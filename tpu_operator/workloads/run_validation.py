"""Workload-pod entry point: the container command of the validator's spawned
pods (cuda/plugin-workload-validation.yaml image analogue).

Exits 0 iff every requested check passes; prints one JSON line per check so
the validator (and humans reading pod logs) see the numbers.

Env:
- ``WORKLOAD_CHECKS``: comma list of vector-add,allreduce,burn-in,matmul
  (default runs the first three; matmul is opt-in — it holds the chip for
  ~0.1 s per size)
- ``ALLREDUCE_SIZE_MB`` / ``ALLREDUCE_MIN_GBPS``: benchmark knobs; the
  minimum enforces the BASELINE "expected ICI GB/s" gate when set
- ``MATMUL_MIN_MFU``: fail the matmul check below this model-flops
  utilization (0 = report only)
"""

from __future__ import annotations

import json
import os
import sys


def main() -> int:
    from tpu_operator.workloads import collectives

    checks = [
        c.strip()
        for c in os.environ.get("WORKLOAD_CHECKS", "vector-add,allreduce,burn-in").split(",")
        if c.strip()
    ]
    ok = True
    for check in checks:
        if check == "vector-add":
            result = collectives.vector_add()
        elif check == "allreduce":
            result = collectives.allreduce_benchmark(
                size_mb=float(os.environ.get("ALLREDUCE_SIZE_MB", "64"))
            )
            min_gbps = float(os.environ.get("ALLREDUCE_MIN_GBPS", "0"))
            if result["transport"] != "ici":
                min_gbps = 0  # single chip: an HBM copy rate, not ICI; never gate
            gated = [
                b.strip()
                for b in os.environ.get("ALLREDUCE_GATE_BACKENDS", "tpu").split(",")
            ]
            if result["backend"] not in gated:
                min_gbps = 0  # CPU/gloo rates say nothing about ICI health
            if result.get("overhead_dominated"):
                # the measurement floor swamped the collective — the number
                # is reported (deflated) but cannot be gated either way
                min_gbps = 0
            # busbw is the link-rate-comparable number (NCCL-tests
            # convention) and what the catalogue expectation describes
            if min_gbps and result["busbw_gbps"] < min_gbps:
                result["ok"] = False
                result["error"] = f"busbw {result['busbw_gbps']:.1f} < required {min_gbps}"
        elif check == "burn-in":
            result = collectives.burn_in()
        elif check == "matmul":
            from tpu_operator.workloads import matmul_bench

            result = matmul_bench.quick_benchmark()
            min_mfu = float(os.environ.get("MATMUL_MIN_MFU", "0"))
            if min_mfu and result["mfu"] is not None and result["mfu"] < min_mfu:
                result["ok"] = False
                result["error"] = f"mfu {result['mfu']:.3f} < required {min_mfu}"
        else:
            result = {"ok": False, "error": f"unknown check {check}"}
        print(json.dumps({"check": check, **result}), flush=True)
        ok = ok and bool(result.get("ok"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
