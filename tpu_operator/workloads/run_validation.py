"""Workload-pod entry point: the container command of the validator's spawned
pods (cuda/plugin-workload-validation.yaml image analogue).

Exits 0 iff every requested check passes; prints one JSON line per check so
the validator (and humans reading pod logs) see the numbers.

Env:
- ``WORKLOAD_CHECKS``: comma list of vector-add,allreduce,burn-in (default all)
- ``ALLREDUCE_SIZE_MB`` / ``ALLREDUCE_MIN_GBPS``: benchmark knobs; the
  minimum enforces the BASELINE "expected ICI GB/s" gate when set
"""

from __future__ import annotations

import json
import os
import sys


def main() -> int:
    from tpu_operator.workloads import collectives

    checks = [
        c.strip()
        for c in os.environ.get("WORKLOAD_CHECKS", "vector-add,allreduce,burn-in").split(",")
        if c.strip()
    ]
    ok = True
    for check in checks:
        if check == "vector-add":
            result = collectives.vector_add()
        elif check == "allreduce":
            result = collectives.allreduce_benchmark(
                size_mb=float(os.environ.get("ALLREDUCE_SIZE_MB", "64"))
            )
            min_gbps = float(os.environ.get("ALLREDUCE_MIN_GBPS", "0"))
            if result["transport"] != "ici":
                min_gbps = 0  # single chip: an HBM copy rate, not ICI; never gate
            if min_gbps and result["algbw_gbps"] < min_gbps:
                result["ok"] = False
                result["error"] = f"algbw {result['algbw_gbps']:.1f} < required {min_gbps}"
        elif check == "burn-in":
            result = collectives.burn_in()
        else:
            result = {"ok": False, "error": f"unknown check {check}"}
        print(json.dumps({"check": check, **result}), flush=True)
        ok = ok and bool(result.get("ok"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
