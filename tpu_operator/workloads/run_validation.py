"""Workload-pod entry point: the container command of the validator's spawned
pods (cuda/plugin-workload-validation.yaml image analogue).

Exits 0 iff every requested check passes; prints one JSON line per check so
the validator (and humans reading pod logs) see the numbers.

Env:
- ``WORKLOAD_CHECKS``: comma list of
  vector-add,allreduce,burn-in,matmul,hbm,hbm-dma,ring,ring-attention,
  ulysses,moe,pipeline,longctx,decode,transformer,transformer-pp,train,
  warm-pool,serving (default
  runs the first three; the rest are opt-in
  — they hold the chip longer; ring is the per-ICI-link diagnostic,
  gated by RING_MIN_GBPS; hbm-dma is the pallas DMA-pipeline
  cross-check, report-only; ring-attention and ulysses are the two
  sequence-parallel long-context acceptances — KV-rotation ring vs
  all-to-all head re-sharding; transformer is the flagship dp+sp+tp
  layer train step)
- ``ALLREDUCE_SIZE_MB`` / ``ALLREDUCE_MIN_GBPS``: benchmark knobs; the
  minimum enforces the BASELINE "expected ICI GB/s" gate when set
- ``MATMUL_MIN_MFU``: fail the matmul check below this model-flops
  utilization (0 = report only)
- ``BURN_IN_SEED``: burn-in params/data seed (default 0) — the concurrent
  partition acceptance gives each partition its own seed
- ``WORKLOAD_BUDGET_S``: stop STARTING new checks past this many seconds
  (a running check finishes; skipped checks are recorded as skipped, not
  failed) — the CR-level perf-probe budget (validator.perfProbes)
- ``WORKLOAD_START_BARRIER`` / ``WORKLOAD_BARRIER_COUNT``: rendezvous dir
  + member count for CONCURRENT runs (partition_acceptance.py): each
  process announces itself in the dir and none runs a check until all
  members are present, so simultaneous execution is proven by
  construction, not by timing luck (``WORKLOAD_BARRIER_TIMEOUT_S``
  bounds the wait, default 120)
"""

from __future__ import annotations

import json
import os
import sys
import time


def check_runners() -> dict:
    """Check name → zero-arg runner, the ONE source of truth for the
    dispatch AND the valid-name set (name validation happens before the
    budget skip, so the two may never drift).  Heavy modules import inside
    each runner — only checks that actually run pay their import."""
    from tpu_operator.workloads import collectives

    def allreduce():
        return collectives.apply_allreduce_gate(
            collectives.allreduce_benchmark(
                size_mb=float(os.environ.get("ALLREDUCE_SIZE_MB", "64"))
            ),
            float(os.environ.get("ALLREDUCE_MIN_GBPS", "0")),
        )

    def burn_in():
        return collectives.burn_in(
            steps=int(os.environ.get("BURN_IN_STEPS", "3") or 3),
            seed=int(os.environ.get("BURN_IN_SEED", "0") or 0),
        )

    def train():
        # end-to-end training throughput: tokens/sec + training MFU of the
        # flagship step at real shapes (report-only evidence for capacity
        # planning; holds the chip ~1min on TPU)
        from tpu_operator.workloads import train_bench

        return train_bench.quick_check()

    def matmul():
        from tpu_operator.workloads import matmul_bench

        return matmul_bench.apply_mfu_gate(
            matmul_bench.quick_benchmark(),
            float(os.environ.get("MATMUL_MIN_MFU", "0")),
        )

    def ring_attention():
        # sequence-parallel exact attention over the local chip ring
        # (long-context acceptance; report-only correctness-or-fail)
        from tpu_operator.workloads import ring_attention as ra

        return ra.quick_check()

    def ulysses():
        # the all-to-all SP strategy (two AllToAlls re-shard seq<->heads);
        # same acceptance contract as ring-attention
        from tpu_operator.workloads import ulysses as ul

        return ul.quick_check()

    def moe():
        # expert parallelism: routed all-to-all dispatch — the only
        # collective here whose traffic crosses EVERY chip pair, so it
        # doubles as a full-bisection interconnect diagnostic
        from tpu_operator.workloads import moe as m

        return m.quick_check()

    def longctx():
        # long-context prefill: K/V-streamed flash attention (32k tokens
        # on one chip), spot-tile exactness + throughput
        from tpu_operator.workloads import longctx as lc

        return lc.quick_check()

    def decode():
        # decode attention against a long KV cache: per-token latency +
        # cache-read bandwidth (the HBM-bound half of serving)
        from tpu_operator.workloads import longctx as lc

        return lc.decode_quick_check()

    def pipeline():
        # GPipe microbatch streaming over chip-resident stages
        from tpu_operator.workloads import pipeline as pl

        return pl.quick_check()

    def warm_pool():
        # the canonical validation programs through the fleet compile-
        # artifact cache: prewarm → compile-or-fetch → execute → publish
        # (workloads/warmpool.py; docs/PERFORMANCE.md "Compile cache &
        # warm-pool validation")
        from tpu_operator.workloads import warmpool

        return warmpool.quick_check()

    def serving():
        # continuous-batching serving engine over the paged KV cache: a
        # small closed-loop A/B — batching must beat sequential scheduling
        # with IDENTICAL per-request outputs (docs/SERVING.md)
        from tpu_operator.workloads import serving as srv

        return srv.quick_check()

    def ring():
        return collectives.apply_ring_gate(
            collectives.ring_benchmark(
                size_mb=float(os.environ.get("RING_SIZE_MB", "16")),
                iters=int(os.environ.get("RING_ITERS", "4")),
            ),
            float(os.environ.get("RING_MIN_GBPS", "0") or 0),
        )

    def hbm():
        from tpu_operator.workloads import hbm_bench

        return hbm_bench.apply_hbm_gate(
            hbm_bench.hbm_benchmark(
                size_mb=float(os.environ.get("HBM_SIZE_MB", "256")),
                iters=int(os.environ.get("HBM_ITERS", "1024")),
                best_of=int(os.environ.get("HBM_BEST_OF", "3")),
            ),
            float(os.environ.get("HBM_MIN_GBPS", "0") or 0),
        )

    def hbm_dma():
        # pallas DMA-pipeline cross-check (report-only by design): same
        # units AND same env-driven working set as hbm — the pair's
        # agreement/divergence is only meaningful over identical sizes
        import jax

        from tpu_operator.workloads import hbm_pallas

        if jax.default_backend() == "tpu":
            return hbm_pallas.dma_stream_benchmark(
                size_mb=float(os.environ.get("HBM_SIZE_MB", "256")),
                iters=int(os.environ.get("HBM_ITERS", "1024")),
                chunk_mb=float(os.environ.get("HBM_DMA_CHUNK_MB", "4")),
                slots=int(os.environ.get("HBM_DMA_SLOTS", "4")),
                best_of=int(os.environ.get("HBM_BEST_OF", "3")),
            )
        # interpret mode: full-size would take minutes in the python DMA
        # emulator — toy shapes, figures labelled cpu
        return hbm_pallas.quick_benchmark()

    return {
        "vector-add": collectives.vector_add,
        "allreduce": allreduce,
        "burn-in": burn_in,
        # the flagship layer: dp batch + mp ring-attention sequence
        # parallelism + Megatron-SP MLP in one train step (opt-in — the
        # gate stays minimal, dryrun/tests prove this composition)
        "transformer": collectives.transformer_burn_in,
        # the full composition: GPipe microbatch pipeline of chip-resident
        # transformer stages, each internally the dp+sp+tp layer
        "transformer-pp": collectives.transformer_pipeline_burn_in,
        "train": train,
        "matmul": matmul,
        "ring-attention": ring_attention,
        "ulysses": ulysses,
        "moe": moe,
        "longctx": longctx,
        "decode": decode,
        "pipeline": pipeline,
        "ring": ring,
        "hbm": hbm,
        "hbm-dma": hbm_dma,
        "warm-pool": warm_pool,
        "serving": serving,
    }


def known_checks() -> set:
    """Valid check names (derived from the dispatch — cannot drift)."""
    return set(check_runners())


def main() -> int:
    from tpu_operator import workloads
    from tpu_operator.workloads import collectives, compile_cache

    workloads.honor_cpu_platform_request()
    compile_cache.enable()
    import jax  # after the platform guard: first import may init a backend

    checks = [
        c.strip()
        for c in os.environ.get("WORKLOAD_CHECKS", "vector-add,allreduce,burn-in").split(",")
        if c.strip()
    ]
    ok = True
    results: dict[str, dict] = {}

    # concurrent-run start barrier (partition acceptance): announce, then
    # hold until every member is present — only then is "these partitions
    # ran SIMULTANEOUSLY" a fact rather than a race outcome
    barrier_dir = os.environ.get("WORKLOAD_START_BARRIER", "")
    if barrier_dir:
        count = int(os.environ.get("WORKLOAD_BARRIER_COUNT", "1") or 1)
        budget = float(os.environ.get("WORKLOAD_BARRIER_TIMEOUT_S", "120") or 120)
        os.makedirs(barrier_dir, exist_ok=True)
        # tmp+replace: a member crashing mid-announce must not leave a torn
        # .ready file the barrier count would trust
        marker = os.path.join(barrier_dir, f"{os.getpid()}.ready")
        with open(marker + ".tmp", "w") as f:
            f.write(str(os.getpid()))
        os.replace(marker + ".tmp", marker)
        deadline = time.monotonic() + budget
        while True:
            present = [n for n in os.listdir(barrier_dir) if n.endswith(".ready")]
            if len(present) >= count:
                break
            if time.monotonic() > deadline:
                print(json.dumps({
                    "check": "start-barrier", "ok": False,
                    "error": f"only {len(present)}/{count} members after {budget}s",
                }), flush=True)
                return 1
            time.sleep(0.05)

    # device-count truth FIRST: when the validator promised a chip count
    # (EXPECTED_DEVICES, from the node's advertised google.com/tpu), PJRT
    # must have initialized exactly that many devices — a node with dead
    # chips must fail here with the counts, not pass every check on the
    # surviving subset (BENCH_r03: 4 advertised, 1 visible, validation green)
    expected = os.environ.get("EXPECTED_DEVICES", "")
    if expected:
        try:
            result = collectives.device_count_check(int(expected))
        except ValueError:
            # a malformed env must surface as a check result (and the
            # drop-box write below), not a traceback with no evidence
            result = {"ok": False, "error": f"malformed EXPECTED_DEVICES={expected!r}"}
        print(json.dumps({"check": "devices", **result}), flush=True)
        results["devices"] = result
        if not result["ok"]:
            # the remaining checks would measure the wrong topology and
            # bury the real failure under misleading numbers
            checks = []
            ok = False

    try:
        budget = float(os.environ.get("WORKLOAD_BUDGET_S", "0") or 0)
    except ValueError:
        budget = 0.0
    t_start = time.monotonic()

    # local tracer so each check runs under a phase span: per-check
    # durations land in the printed/drop-boxed evidence here, and in the
    # workload_phase_duration histogram when run in an instrumented process.
    # The flight recorder runs alongside it: every check's per-step samples
    # (and a summary sample per result) land in the JSONL flight record
    # beside the results drop-box, tagged with the check span's id — and
    # stream to the node metrics agent when TPU_METRICS_PUSH_URL is set.
    from tpu_operator.obs import flight, trace
    from tpu_operator.validator import status as vstatus

    scope = os.environ.get("RESULTS_SCOPE", "")
    recorder = flight.recorder_for(vstatus.flight_record_path(scope))
    # adopt the propagated trace context (TPU_TRACEPARENT, injected by the
    # validator's pod spec from the operator's rollout trace): the check
    # spans and flight samples below join that trace end to end
    tracer = trace.Tracer()
    runners = check_runners()
    with tracer.adopt(trace.TraceContext.from_env()), flight.activate(recorder):
        for check in checks:
            runner = runners.get(check)
            if runner is None:
                # validate the NAME even past the budget: a typo'd check must
                # fail the pod, never be masked as a benign budget skip
                result = {"ok": False, "error": f"unknown check {check}"}
            elif budget and time.monotonic() - t_start > budget:
                # chip-occupancy budget exhausted: remaining checks are
                # SKIPPED evidence, not failures — the operator chose the
                # budget; a probe that didn't run says nothing bad about
                # the hardware
                result = {"ok": True, "skipped": f"budget ({budget}s) exhausted"}
            else:
                with trace.span(
                    f"check/{check}", kind=trace.KIND_PHASE, phase=check
                ):
                    t0 = time.monotonic()
                    result = runner()
                    result.setdefault(
                        "duration_s", round(time.monotonic() - t0, 6)
                    )
                    # inside the span (the summary sample carries its id),
                    # after the duration default (so it carries that too)
                    flight.record_result(check, result)
            print(json.dumps({"check": check, **result}), flush=True)
            results[check] = result
            ok = ok and bool(result.get("ok"))

    # node-local drop-box: the validator (mounting the same /run/tpu) merges
    # the measured numbers into its payloads → node-status exporter → the
    # perf-degradation alerts; best-effort, never a gate.  RESULTS_SCOPE
    # (injected for the perf-probes pod) keeps probe figures from
    # clobbering the gating run's
    from tpu_operator.validator import status as vstatus

    vstatus.write_workload_results(
        {"checks": results}, scope=os.environ.get("RESULTS_SCOPE", "")
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
