"""Sustained serving plane: continuous batching over a paged KV cache.

Every serving number before this module was a one-shot probe
(``longctx.decode_benchmark``: one request, one cache, per-token latency).
Millions of users means *sustained* traffic: requests of unequal lengths
arriving continuously, sharing one cache region, joining and leaving the
running batch every decode step.  This module is that engine, CPU-runnable
end to end (the chaos soak's payload) and kernel-compatible with the TPU
path:

- :class:`PagedKVCache` — the KV cache as a pool of fixed-size token
  blocks (the compiler-first O(1) autoregressive-cache discipline: state
  lives in pre-allocated pages, appended in place, never reshaped):
  per-request *block tables* map logical token positions to physical
  blocks, allocation/free are O(blocks) list operations with a double-free
  guard, admission is capacity-based (a request is admitted only when its
  worst-case block need fits), and :meth:`PagedKVCache.defrag` compacts
  live blocks into the lowest-numbered slots so the pool's high-water mark
  shrinks after churn.
- :class:`ServingEngine` — iteration-level (continuous-batching)
  scheduling: every :meth:`ServingEngine.step` retires finished requests
  FIRST (their blocks serve this very step's admissions), admits queued
  requests that fit, advances prefill under a per-step token budget
  (chunked, so one long prompt cannot head-of-line-block the running
  batch's decode), and decodes ONE token for every running request in a
  single batched attention call.  Batching never changes results: the
  attention is computed per batch row over length-masked gathered KV, so a
  request's token stream is identical at batch size 1 and 8 (pinned by
  test — the property that makes the throughput A/B meaningful).
- Decode attention runs over KV *gathered from the paged pool*: the
  ``dense`` implementation is a jitted length-masked reference (one
  compile ever — shapes padded to ``max_batch`` × ``max_context``); the
  ``flash`` implementation routes through
  ``longctx.flash_attention_local`` exactly like ``decode_benchmark``
  (8-row query tail, block_k = the KV page size) with the gathered KV
  zero-padded to a block multiple — causal masking kills the padded tail,
  so paged storage composes with the flash kernel unchanged.  Both paths
  produce identical tokens (pinned by test).
- :class:`PoissonTraffic` — seeded arrivals (exponential inter-arrival
  gaps, uniform prompt/new-token ranges), checkpointable: the RNG bit
  state and the next-arrival cursor ride the snapshot so a restored
  replica continues the SAME request schedule without duplicating ids.
- :func:`serve` — the replica main loop: real-time stepping, flight
  samples (``tpu_workload_serving_*`` through the agent push hop), and
  the PR-8 migration contract: on ``tpu.google.com/migrate=requested``
  (``MigrationSignal``) the engine checkpoints its FULL serving state —
  the KV pool arrays, every request's block table and token stream, the
  traffic cursor — via ``workloads/checkpoint.py``'s atomic snapshot
  machinery and exits 0; the restore pod resumes mid-request with the
  cache intact (no prefill is re-paid).
- :func:`batching_ab` — the acceptance A/B: the same seeded closed-loop
  request set through sequential (one-request-at-a-time) and
  continuous-batching scheduling at the SAME compiled batch shape,
  returning aggregate tokens/sec and per-request TPOT for both — the
  ``bench.py --serve`` ≥2x gate.

Env contract (docs/SERVING.md): ``TPU_SERVE_RATE`` / ``TPU_SERVE_SECONDS``
/ ``TPU_SERVE_SEED`` / ``TPU_SERVE_BLOCKS`` / ``TPU_SERVE_BLOCK_TOKENS``
/ ``TPU_SERVE_MAX_BATCH`` / ``TPU_SERVE_PREFILL_BUDGET`` /
``TPU_SERVE_PROMPT_TOKENS`` / ``TPU_SERVE_NEW_TOKENS`` /
``TPU_SERVE_NAME`` / ``TPU_SERVE_STEP_INTERVAL_S`` plus the shared
``TPU_CKPT_DIR`` / ``TPU_MIGRATE_SIGNAL_FILE`` / ``TPU_JOB_RESULT_FILE``
migration/drop-box contract.
"""

from __future__ import annotations

import functools
import heapq
import json
import math
import os
import sys
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from tpu_operator import consts
from tpu_operator.obs import flight
from tpu_operator.obs import profile as obs_profile
from tpu_operator.workloads import checkpoint as ckpt_api

# environment contract (docs/SERVING.md "Env contract")
RATE_ENV = "TPU_SERVE_RATE"
SECONDS_ENV = "TPU_SERVE_SECONDS"
SEED_ENV = "TPU_SERVE_SEED"
BLOCKS_ENV = "TPU_SERVE_BLOCKS"
BLOCK_TOKENS_ENV = "TPU_SERVE_BLOCK_TOKENS"
MAX_BATCH_ENV = "TPU_SERVE_MAX_BATCH"
PREFILL_BUDGET_ENV = "TPU_SERVE_PREFILL_BUDGET"
PROMPT_TOKENS_ENV = "TPU_SERVE_PROMPT_TOKENS"
NEW_TOKENS_ENV = "TPU_SERVE_NEW_TOKENS"
NAME_ENV = "TPU_SERVE_NAME"
STEP_INTERVAL_ENV = "TPU_SERVE_STEP_INTERVAL_S"

# request states
QUEUED = "queued"
PREFILL = "prefill"
RUNNING = "running"
DONE = "done"
CANCELLED = "cancelled"

# rolling-stat window sizes (samples, not seconds): enough for a stable
# p99, small enough that a migration-era spike ages out of the pushed
# gauges within a few hundred steps
_ROLLING_SAMPLES = 512
_RATE_WINDOW_S = 5.0
# minimum evidence span before a rolling rate is reported: a single-step
# history would divide a batch of tokens by (nearly) zero seconds and
# push an absurdly inflated gauge into the SLO feed on every ramp-up
_RATE_MIN_SPAN_S = 0.5


def _percentile(values: list[float], frac: float) -> float:
    """Index percentile over an ASCENDING list (0 when empty) — the one
    convention the rolling gauges, the replica result, and the A/B gate
    all share."""
    if not values:
        return 0.0
    return float(values[min(len(values) - 1, int(frac * len(values)))])


class ServingError(Exception):
    """A request the engine cannot ever serve (oversize, bad shape)."""


# ---------------------------------------------------------------------------
# Paged KV cache.


class PagedKVCache:
    """Fixed-size-block KV pool shared by every in-flight request.

    K and V live as ``[num_blocks, block_tokens, heads, head_dim]`` numpy
    arrays; a request owns an ordered *block table* (list of physical
    block ids) and its logical token position ``p`` lives at
    ``(table[p // block_tokens], p % block_tokens)``.  Allocation pops
    from a free list ATOMICALLY — check and take are one synchronous
    operation with no await point between them, which is the whole
    admission-race story (tests/test_race.py drives the interleavings and
    proves a split check-then-take double-allocates).
    """

    def __init__(
        self,
        num_blocks: int,
        block_tokens: int,
        heads: int,
        head_dim: int,
        dtype=np.float32,
    ):
        if num_blocks <= 0 or block_tokens <= 0:
            raise ServingError("num_blocks and block_tokens must be positive")
        self.num_blocks = num_blocks
        self.block_tokens = block_tokens
        self.heads = heads
        self.head_dim = head_dim
        self.k = np.zeros((num_blocks, block_tokens, heads, head_dim), dtype)
        self.v = np.zeros_like(self.k)
        # min-heap free list: the smallest block id pops first so
        # low-numbered blocks are preferred (keeps the high-water mark
        # honest without defrag) at O(log n) per alloc/free
        self._free: list[int] = list(range(num_blocks))
        self._free_set: set[int] = set(self._free)
        self.alloc_failures = 0

    # -- allocation ----------------------------------------------------
    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.num_blocks - len(self._free)

    def blocks_for_tokens(self, tokens: int) -> int:
        return max(1, math.ceil(tokens / self.block_tokens))

    def try_alloc(self, n: int) -> Optional[list[int]]:
        """``n`` blocks, or None when the pool cannot satisfy the request
        — the capacity-based admission check and the take are ONE atomic
        operation (no await/yield between them)."""
        if n <= 0:
            raise ServingError(f"alloc of {n} blocks")
        if len(self._free) < n:
            self.alloc_failures += 1
            return None
        blocks = [heapq.heappop(self._free) for _ in range(n)]
        self._free_set.difference_update(blocks)
        return blocks

    def free(self, blocks: list[int]) -> None:
        for b in blocks:
            if b in self._free_set or not (0 <= b < self.num_blocks):
                raise ServingError(f"double-free of KV block {b}")
            self._free_set.add(b)
            heapq.heappush(self._free, b)

    def high_water(self) -> int:
        """Highest used block id + 1 (0 when idle): the pool prefix a
        contiguous-arena backend would have to keep resident."""
        used = set(range(self.num_blocks)) - self._free_set
        return (max(used) + 1) if used else 0

    def defrag(self, tables: dict[str, list[int]]) -> int:
        """Compact live blocks into the lowest-numbered free slots,
        rewriting the given block tables in place; returns moves made.
        Fixed-size paging has no *external* fragmentation — any free block
        serves any request — but a scattered pool pins a high high-water
        mark (the resident-prefix cost above) and smears gathers across
        the arena; compaction after a churn burst undoes that."""
        moves = 0
        for table in tables.values():
            for i, src in enumerate(table):
                if not self._free or self._free[0] >= src:
                    continue  # heap root IS the min: nothing lower is free
                dst = heapq.heappop(self._free)
                self._free_set.discard(dst)
                self.k[dst] = self.k[src]
                self.v[dst] = self.v[src]
                table[i] = dst
                self._free_set.add(src)
                heapq.heappush(self._free, src)
                moves += 1
        return moves

    # -- token I/O -----------------------------------------------------
    def write_tokens(
        self, table: list[int], start: int, k: np.ndarray, v: np.ndarray
    ) -> None:
        """Scatter ``k``/``v`` (``[T, heads, head_dim]``) for logical
        positions ``start .. start+T-1`` into the request's blocks."""
        bt = self.block_tokens
        for i in range(k.shape[0]):
            pos = start + i
            block = table[pos // bt]
            slot = pos % bt
            self.k[block, slot] = k[i]
            self.v[block, slot] = v[i]

    def gather(
        self, table: list[int], length: int, pad_to: Optional[int] = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """Contiguous ``[pad_to, heads, head_dim]`` K and V for the first
        ``length`` logical tokens (zero-padded past them) — the paged →
        contiguous hop in front of the attention kernel."""
        bt = self.block_tokens
        pad_to = length if pad_to is None else pad_to
        nb = math.ceil(length / bt)
        out_k = np.zeros((pad_to, self.heads, self.head_dim), self.k.dtype)
        out_v = np.zeros_like(out_k)
        if nb:
            idx = np.asarray(table[:nb])
            flat_k = self.k[idx].reshape(nb * bt, self.heads, self.head_dim)
            flat_v = self.v[idx].reshape(nb * bt, self.heads, self.head_dim)
            out_k[:length] = flat_k[:length]
            out_v[:length] = flat_v[:length]
        return out_k, out_v

    # -- invariants ----------------------------------------------------
    def check_integrity(self, tables: dict[str, list[int]]) -> None:
        """Every live table disjoint from every other AND from the free
        list, and together they account for the whole pool — the
        double-allocation invariant the race suite sweeps."""
        seen: dict[int, str] = {}
        for rid, table in tables.items():
            for b in table:
                if b in seen:
                    raise ServingError(
                        f"KV block {b} double-allocated: {seen[b]} and {rid}"
                    )
                if b in self._free_set:
                    raise ServingError(
                        f"KV block {b} owned by {rid} AND on the free list"
                    )
                seen[b] = rid
        if len(self._free) != len(self._free_set):
            raise ServingError("free list/set diverged")
        if len(seen) + len(self._free) != self.num_blocks:
            # EXACT accounting, both directions: over-commit is a double
            # booking, a shortfall is a LEAKED block (released from a
            # table without reaching the free list) — the race sweep needs
            # the step-level localization either way
            raise ServingError(
                f"pool accounting broken: {len(seen)} owned + "
                f"{len(self._free)} free != {self.num_blocks}"
            )


# ---------------------------------------------------------------------------
# Toy deterministic LM: enough model to make serving real (per-position
# Q/K/V, causal attention over the cache, greedy next-token) while staying
# seed-reproducible so checkpoint/restore and batch-invariance are
# bit-checkable.


class ToyLM:
    def __init__(
        self,
        vocab: int = 128,
        heads: int = 2,
        head_dim: int = 16,
        max_context: int = 256,
        seed: int = 0,
    ):
        self.vocab = vocab
        self.heads = heads
        self.head_dim = head_dim
        self.max_context = max_context
        self.seed = seed
        d = heads * head_dim
        rng = np.random.default_rng(seed)
        scale = 1.0 / math.sqrt(d)
        self.emb = (rng.standard_normal((vocab, d)) * 0.5).astype(np.float32)
        self.wq = (rng.standard_normal((d, d)) * scale).astype(np.float32)
        self.wk = (rng.standard_normal((d, d)) * scale).astype(np.float32)
        self.wv = (rng.standard_normal((d, d)) * scale).astype(np.float32)
        self.wu = (rng.standard_normal((d, vocab)) * scale).astype(np.float32)
        # sinusoidal positions: KV must depend on position or the cache
        # would be content-addressable and the paging untestable
        pos = np.arange(max_context)[:, None]
        freq = np.exp(-np.arange(0, d, 2) * (math.log(10000.0) / d))[None, :]
        table = np.zeros((max_context, d), np.float32)
        table[:, 0::2] = np.sin(pos * freq)
        table[:, 1::2] = np.cos(pos * freq)
        self.pos = table

    def _x(self, tokens: np.ndarray, positions: np.ndarray) -> np.ndarray:
        return self.emb[tokens] + self.pos[positions]

    def qkv(
        self, tokens: np.ndarray, positions: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """``[T, heads, head_dim]`` Q, K, V for the given token ids at the
        given positions."""
        x = self._x(np.asarray(tokens), np.asarray(positions))
        shape = (x.shape[0], self.heads, self.head_dim)
        return (
            (x @ self.wq).reshape(shape),
            (x @ self.wk).reshape(shape),
            (x @ self.wv).reshape(shape),
        )

    def next_token(self, attended: np.ndarray) -> int:
        """Greedy decode from one position's attended output
        (``[heads, head_dim]``)."""
        logits = attended.reshape(-1) @ self.wu
        return int(np.argmax(logits))


@functools.lru_cache(maxsize=8)
def _dense_attend(max_batch: int, max_context: int, heads: int, head_dim: int):
    """One jitted length-masked decode attention per engine SHAPE (not per
    engine instance): q ``[B, H, D]`` against gathered KV
    ``[B, C, H, D]`` with per-row valid lengths.  Rows are independent —
    the batch-invariance property the determinism test pins."""
    import jax
    import jax.numpy as jnp

    scale = 1.0 / math.sqrt(head_dim)

    @jax.jit
    def attend(q, k, v, lengths):
        s = jnp.einsum("bhd,bchd->bhc", q, k) * scale
        mask = jnp.arange(max_context)[None, None, :] < lengths[:, None, None]
        s = jnp.where(mask, s, -1e30)
        w = jax.nn.softmax(s, axis=-1)
        w = jnp.where(mask, w, 0.0)
        return jnp.einsum("bhc,bchd->bhd", w, v)

    return attend


# ---------------------------------------------------------------------------
# Requests and traffic.


@dataclass
class Request:
    rid: str
    prompt: list[int]
    max_new_tokens: int
    arrival: float
    state: str = QUEUED
    blocks: list[int] = field(default_factory=list)
    prefilled: int = 0
    tokens: list[int] = field(default_factory=list)
    first_token_at: Optional[float] = None
    last_token_at: Optional[float] = None
    done_at: Optional[float] = None
    tpot_samples: list[float] = field(default_factory=list)

    def __post_init__(self):
        if not self.tokens:
            self.tokens = list(self.prompt)

    @property
    def generated(self) -> int:
        return len(self.tokens) - len(self.prompt)

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.arrival

    def to_snapshot(self) -> dict:
        return {
            "rid": self.rid,
            "prompt": list(self.prompt),
            "max_new_tokens": self.max_new_tokens,
            "arrival": self.arrival,
            "state": self.state,
            "blocks": list(self.blocks),
            "prefilled": self.prefilled,
            "tokens": list(self.tokens),
            "first_token_at": self.first_token_at,
            "last_token_at": self.last_token_at,
            "tpot_samples": list(self.tpot_samples),
        }

    @classmethod
    def from_snapshot(cls, data: dict) -> "Request":
        req = cls(
            rid=data["rid"],
            prompt=list(data["prompt"]),
            max_new_tokens=int(data["max_new_tokens"]),
            arrival=float(data["arrival"]),
            state=data["state"],
            blocks=list(data["blocks"]),
            prefilled=int(data["prefilled"]),
            tokens=list(data["tokens"]),
            first_token_at=data.get("first_token_at"),
            last_token_at=data.get("last_token_at"),
            tpot_samples=list(data.get("tpot_samples") or []),
        )
        return req


class PoissonTraffic:
    """Seeded open-loop arrivals: exponential gaps at ``rate`` requests/s,
    uniform prompt/new-token draws.  The full generator state (RNG bit
    state + arrival cursor + id counter) serializes into the serving
    checkpoint so a migrated replica continues the schedule, not restarts
    it."""

    def __init__(
        self,
        rate: float,
        prompt_tokens: tuple[int, int] = (24, 64),
        new_tokens: tuple[int, int] = (12, 32),
        vocab: int = 128,
        seed: int = 0,
        prefix: str = "req",
    ):
        self.rate = rate
        self.prompt_tokens = prompt_tokens
        self.new_tokens = new_tokens
        self.vocab = vocab
        self.prefix = prefix
        self.rng = np.random.default_rng(seed)
        self.next_id = 0
        self.next_at = self._gap()

    def _gap(self) -> float:
        if self.rate <= 0:
            return float("inf")
        return float(self.rng.exponential(1.0 / self.rate))

    def _mint(self, arrival: float) -> Request:
        plo, phi = self.prompt_tokens
        nlo, nhi = self.new_tokens
        prompt_len = int(self.rng.integers(plo, phi + 1))
        new = int(self.rng.integers(nlo, nhi + 1))
        prompt = [int(t) for t in self.rng.integers(0, self.vocab, prompt_len)]
        req = Request(
            rid=f"{self.prefix}-{self.next_id}",
            prompt=prompt,
            max_new_tokens=new,
            arrival=arrival,
        )
        self.next_id += 1
        return req

    def due(self, now: float) -> list[Request]:
        if self.rate > 0 and self.next_at == float("inf"):
            # stream re-enabled after a rate<=0 quiesce (or constructed
            # quiesced against a wall-clock ``now``): restart the arrival
            # schedule from the caller's clock, not from zero
            self.next_at = now + self._gap()
        out = []
        while self.next_at <= now:
            out.append(self._mint(self.next_at))
            self.next_at += self._gap()
        return out

    def state(self) -> dict:
        return {
            "rate": self.rate,
            "next_id": self.next_id,
            "next_at": self.next_at,
            "rng": self.rng.bit_generator.state,
        }

    def restore(self, state: dict) -> None:
        self.next_id = int(state["next_id"])
        self.next_at = float(state["next_at"])
        self.rng.bit_generator.state = state["rng"]


# ---------------------------------------------------------------------------
# The engine.


@dataclass
class ServeConfig:
    vocab: int = 128
    heads: int = 2
    head_dim: int = 16
    num_blocks: int = 96
    block_tokens: int = 16
    max_batch: int = 8
    max_context: int = 128
    prefill_budget: int = 64
    # admission width: continuous batching admits up to max_batch; the
    # sequential baseline admits ONE request at a time (same compiled
    # shapes, different scheduling — the only variable in the A/B)
    admit_limit: int = 0  # 0 = max_batch
    attend: str = "dense"  # dense | flash (flash = longctx kernel path)
    model_seed: int = 0
    name: str = "serving"

    def __post_init__(self):
        if self.max_context % self.block_tokens:
            raise ServingError("max_context must be a block_tokens multiple")

    @property
    def admission_width(self) -> int:
        return self.admit_limit or self.max_batch


class ServingEngine:
    """Iteration-level scheduler over one :class:`PagedKVCache`."""

    def __init__(self, cfg: ServeConfig, model: Optional[ToyLM] = None):
        self.cfg = cfg
        self.model = model or ToyLM(
            vocab=cfg.vocab, heads=cfg.heads, head_dim=cfg.head_dim,
            max_context=cfg.max_context, seed=cfg.model_seed,
        )
        self.cache = PagedKVCache(
            cfg.num_blocks, cfg.block_tokens, cfg.heads, cfg.head_dim
        )
        self.queued: deque[Request] = deque()
        self.prefilling: list[Request] = []
        self.running: list[Request] = []
        self.steps = 0
        self.tokens_generated = 0
        self.requests_completed = 0
        self.requests_rejected = 0
        self.requests_cancelled = 0
        # rolling stats (samples, newest-first irrelevant — percentiles)
        self._ttft: deque[float] = deque(maxlen=_ROLLING_SAMPLES)
        self._tpot: deque[float] = deque(maxlen=_ROLLING_SAMPLES)
        self._token_times: deque[tuple[float, int]] = deque(maxlen=4096)
        self._completions: list[dict] = []

    # -- submission ----------------------------------------------------
    def submit(self, req: Request) -> bool:
        """Queue a request; False (counted) when it can never fit — over
        the context bound OR over the WHOLE pool's block count.  The pool
        check matters independently: an unserviceable request reaching the
        queue head would wedge the FIFO forever (admission never overtakes
        a starved head, and serve() waits for the queue to drain)."""
        total = len(req.prompt) + req.max_new_tokens
        if (
            not req.prompt
            or total > self.cfg.max_context
            or self.cache.blocks_for_tokens(total) > self.cache.num_blocks
        ):
            self.requests_rejected += 1
            return False
        self.queued.append(req)
        return True

    def cancel(self, rid: str) -> bool:
        """Client went away: drop the request wherever it stands and free
        its blocks immediately."""
        for req in list(self.queued):
            if req.rid == rid:
                self.queued.remove(req)
                req.state = CANCELLED
                self.requests_cancelled += 1
                return True
        for bucket in (self.prefilling, self.running):
            for req in bucket:
                if req.rid == rid:
                    bucket.remove(req)
                    self._release(req, CANCELLED)
                    self.requests_cancelled += 1
                    return True
        return False

    def _release(self, req: Request, state: str) -> None:
        if req.blocks:
            self.cache.free(req.blocks)
            req.blocks = []
        req.state = state

    # -- scheduling ----------------------------------------------------
    def _blocks_needed(self, req: Request) -> int:
        return self.cache.blocks_for_tokens(
            len(req.prompt) + req.max_new_tokens
        )

    def _admit(self) -> int:
        """FIFO capacity-based admission: a queued request joins only when
        its WORST-CASE block need allocates (no mid-decode OOM, ever) and
        the batch has a seat.  The check and the allocation are one atomic
        ``try_alloc`` — see the race suite."""
        admitted = 0
        width = self.cfg.admission_width
        while self.queued:
            active = len(self.prefilling) + len(self.running)
            if active >= min(width, self.cfg.max_batch):
                break
            req = self.queued[0]
            blocks = self.cache.try_alloc(self._blocks_needed(req))
            if blocks is None:
                break  # FIFO: no overtaking past a starved head
            self.queued.popleft()
            req.blocks = blocks
            req.state = PREFILL
            req.prefilled = 0
            self.prefilling.append(req)
            admitted += 1
        return admitted

    def _prefill(self) -> int:
        """Advance prefill across admitted requests under the per-step
        token budget (chunked: a long prompt spreads over iterations
        instead of blocking the batch's decode)."""
        budget = self.cfg.prefill_budget
        done: list[Request] = []
        for req in self.prefilling:
            if budget <= 0:
                break
            take = min(budget, len(req.prompt) - req.prefilled)
            if take > 0:
                start = req.prefilled
                chunk = np.asarray(req.prompt[start:start + take])
                positions = np.arange(start, start + take)
                _, k, v = self.model.qkv(chunk, positions)
                self.cache.write_tokens(req.blocks, start, k, v)
                req.prefilled += take
                budget -= take
            if req.prefilled >= len(req.prompt):
                done.append(req)
        for req in done:
            self.prefilling.remove(req)
            req.state = RUNNING
            self.running.append(req)
        return len(done)

    # -- decode --------------------------------------------------------
    def _attend_dense(self, reqs: list[Request], qs: np.ndarray) -> np.ndarray:
        cfg = self.cfg
        B = cfg.max_batch
        k = np.zeros((B, cfg.max_context, cfg.heads, cfg.head_dim), np.float32)
        v = np.zeros_like(k)
        lengths = np.zeros((B,), np.int32)
        q = np.zeros((B, cfg.heads, cfg.head_dim), np.float32)
        for i, req in enumerate(reqs):
            length = len(req.tokens)
            gk, gv = self.cache.gather(req.blocks, length, pad_to=cfg.max_context)
            k[i], v[i] = gk, gv
            lengths[i] = length
            q[i] = qs[i]
        attend = _dense_attend(B, cfg.max_context, cfg.heads, cfg.head_dim)
        out = np.asarray(attend(q, k, v, lengths))
        return out[: len(reqs)]

    def _attend_flash(self, reqs: list[Request], qs: np.ndarray) -> np.ndarray:
        """The TPU-kernel path: per request, ``longctx.flash_attention_local``
        with an 8-row query tail (``decode_benchmark``'s exact shape) over
        gathered KV zero-padded to a block multiple — padded keys sit at
        positions past every query, so the kernel's causal masking drops
        them and paged storage composes with the flash kernel unchanged."""
        from tpu_operator.workloads import longctx

        tail = 8
        cfg = self.cfg
        out = np.zeros((len(reqs), cfg.heads, cfg.head_dim), np.float32)
        for i, req in enumerate(reqs):
            length = len(req.tokens)
            if length < tail:
                # a tail shorter than the Mosaic row minimum: dense fallback
                out[i] = self._attend_dense([req], qs[i:i + 1])[0]
                continue
            pad = cfg.block_tokens * math.ceil(length / cfg.block_tokens)
            gk, gv = self.cache.gather(req.blocks, length, pad_to=pad)
            # [T, H, D] -> merged [BH=H, T, D]
            km = np.ascontiguousarray(gk.transpose(1, 0, 2))
            vm = np.ascontiguousarray(gv.transpose(1, 0, 2))
            toks = np.asarray(req.tokens[length - tail:length])
            positions = np.arange(length - tail, length)
            qt, _, _ = self.model.qkv(toks, positions)
            qm = np.ascontiguousarray(qt.transpose(1, 0, 2))
            o, _ = longctx.flash_attention_local(
                qm, km, vm, causal=True,
                block_k=cfg.block_tokens, block_q=tail,
                q_off=length - tail,
            )
            out[i] = np.asarray(o)[:, -1, :]
        return out

    def _decode(self, now: float) -> int:
        reqs = self.running[: self.cfg.max_batch]
        if not reqs:
            return 0
        # q from each request's LAST token at its position — one vectorized
        # projection for the whole batch (a per-request loop here would tax
        # exactly the batched path the scheduler exists to win on)
        qs, _, _ = self.model.qkv(
            np.asarray([req.tokens[-1] for req in reqs]),
            np.asarray([len(req.tokens) - 1 for req in reqs]),
        )
        if self.cfg.attend == "flash":
            attended = self._attend_flash(reqs, qs)
        else:
            attended = self._attend_dense(reqs, qs)
        # greedy next tokens for the whole batch in one projection
        logits = attended.reshape(len(reqs), -1) @ self.model.wu
        next_tokens = np.argmax(logits, axis=-1)
        finished: list[Request] = []
        continuing: list[tuple[Request, int, int]] = []
        for i, req in enumerate(reqs):
            token = int(next_tokens[i])
            pos = len(req.tokens)
            req.tokens.append(token)
            self.tokens_generated += 1
            if req.first_token_at is None:
                req.first_token_at = now
                self._ttft.append(req.ttft_s or 0.0)
            else:
                # first_token_at set implies last_token_at set — and it may
                # legitimately be 0.0 (explicit-clock callers), so no falsy
                # fallback: `or now` here zeroed the first TPOT sample
                interval = now - req.last_token_at
                req.tpot_samples.append(interval)
                self._tpot.append(interval)
            req.last_token_at = now
            if req.generated >= req.max_new_tokens:
                finished.append(req)
            else:
                continuing.append((req, token, pos))
        if continuing:
            # the new tokens' KV joins the cache (block seats were reserved
            # at admission — appends can never OOM mid-flight); one
            # vectorized projection, scattered per request
            _, ks, vs = self.model.qkv(
                np.asarray([t for _, t, _ in continuing]),
                np.asarray([p for _, _, p in continuing]),
            )
            for i, (req, _, pos) in enumerate(continuing):
                self.cache.write_tokens(req.blocks, pos, ks[i:i + 1], vs[i:i + 1])
        self._token_times.append((now, len(reqs)))
        for req in finished:
            self.running.remove(req)
            req.done_at = now
            self._completions.append({
                "rid": req.rid,
                "tokens": req.generated,
                "ttft_s": req.ttft_s,
                "tpot_mean_s": (
                    sum(req.tpot_samples) / len(req.tpot_samples)
                    if req.tpot_samples else 0.0
                ),
            })
            self._release(req, DONE)
            self.requests_completed += 1
        return len(finished)

    # -- the iteration -------------------------------------------------
    def step(self, now: Optional[float] = None) -> dict:
        """One continuous-batching iteration: retire → admit → prefill →
        decode.  Retirement runs FIRST so blocks freed by finishing
        requests serve this same step's admissions (retirement itself
        happens at the end of the previous decode; this ordering note is
        the scheduling contract the race suite interleaves)."""
        now = time.monotonic() if now is None else now
        self.steps += 1
        admitted = self._admit()
        prefilled = self._prefill()
        finished = self._decode(now)
        return {
            "now": now,
            "admitted": admitted,
            "prefill_completed": prefilled,
            "finished": finished,
            "queue_depth": len(self.queued),
            "batch": len(self.running),
            "prefilling": len(self.prefilling),
            "kv_blocks_free": self.cache.free_count,
        }

    @property
    def active(self) -> int:
        return len(self.queued) + len(self.prefilling) + len(self.running)

    def block_tables(self) -> dict[str, list[int]]:
        return {
            req.rid: req.blocks
            for req in (*self.prefilling, *self.running)
            if req.blocks
        }

    def check_integrity(self) -> None:
        self.cache.check_integrity(self.block_tables())

    # -- rolling telemetry --------------------------------------------
    @staticmethod
    def _p99(samples) -> float:
        return _percentile(sorted(samples), 0.99)

    def tokens_per_sec(self, now: Optional[float] = None) -> Optional[float]:
        """Rolling decode rate, or None while the window holds too little
        evidence to divide by — a fresh ramp's single-step history must
        not push a near-zero-span (and so wildly inflated) rate into the
        SLO feed.  0.0 means a live batch produced nothing all window: a
        genuine stall."""
        now = time.monotonic() if now is None else now
        cutoff = now - _RATE_WINDOW_S
        recent = [(ts, n) for ts, n in self._token_times if ts >= cutoff]
        if not recent:
            return 0.0 if self.running else None
        span = now - recent[0][0]
        if span < _RATE_MIN_SPAN_S:
            return None
        return sum(n for _, n in recent) / span

    def telemetry(self, now: Optional[float] = None) -> dict:
        """The flight-sample metric map (obs/flight COUNTER_KEYS names →
        the ``tpu_workload_serving_*`` catalogue).

        ``serve_tokens_per_sec`` is emitted only when the rate window
        holds enough evidence to divide by (:meth:`tokens_per_sec`): an
        idle replica (warm-up, drain tail, traffic gap) and a
        just-ramping batch both go DARK on the throughput gauge instead
        of pushing zeros or near-zero-span inflated rates — idle is not
        degraded, and the PR-6 burn-rate engine's no-evidence semantics
        are exactly the right judge for a quiet gauge.  A pushed 0 means
        a live batch produced nothing all window: a genuine stall the
        throughput SLO must fire on."""
        out = {
            "serve_ttft_p99_s": round(self._p99(self._ttft), 6),
            "serve_tpot_p99_s": round(self._p99(self._tpot), 6),
            "serve_queue_depth": float(len(self.queued)),
            "serve_batch_size": float(len(self.running)),
            "serve_kv_blocks_free": float(self.cache.free_count),
            "serve_requests_completed": float(self.requests_completed),
            "serve_requests_rejected": float(self.requests_rejected),
            # cumulative decode output: the chip-time ledger's busy_useful
            # evidence for serving replicas (a push whose token counter
            # advanced marks the inter-push gap as useful chip-time)
            "serve_decoded_tokens": float(self.tokens_generated),
        }
        tps = self.tokens_per_sec(now)
        if tps is not None:
            out["serve_tokens_per_sec"] = round(tps, 3)
        return out

    def completions(self) -> list[dict]:
        return list(self._completions)

    # -- checkpoint/restore (the PR-8 migration contract) --------------
    def snapshot(self) -> tuple[dict, dict]:
        """(arrays, extra) for ``checkpoint.save_checkpoint``: the KV pool
        rides as shard-hashed arrays, the request/traffic bookkeeping as
        the JSON ``extra`` — restore resumes every in-flight request with
        its cache intact (prefill is never re-paid)."""
        arrays = {"kv_k": self.cache.k, "kv_v": self.cache.v}
        extra = {
            "config": {
                "vocab": self.cfg.vocab,
                "heads": self.cfg.heads,
                "head_dim": self.cfg.head_dim,
                "num_blocks": self.cfg.num_blocks,
                "block_tokens": self.cfg.block_tokens,
                "max_context": self.cfg.max_context,
                "model_seed": self.cfg.model_seed,
            },
            "steps": self.steps,
            "tokens_generated": self.tokens_generated,
            "requests_completed": self.requests_completed,
            "requests_rejected": self.requests_rejected,
            "requests_cancelled": self.requests_cancelled,
            "requests": [
                req.to_snapshot()
                for req in (*self.queued, *self.prefilling, *self.running)
            ],
            # latency evidence rides too: the restored replica's final
            # result must report LIFETIME percentiles, not just the
            # post-restore tail — the soak's serving_p99_ms gate reads the
            # newest result event as whole-life coverage
            "completions": list(self._completions),
            "ttft_samples": [float(v) for v in self._ttft],
            "tpot_samples": [float(v) for v in self._tpot],
        }
        return arrays, extra

    @classmethod
    def from_snapshot(
        cls, cfg: ServeConfig, arrays: dict, extra: dict
    ) -> "ServingEngine":
        saved = extra.get("config") or {}
        for key in ("heads", "head_dim", "num_blocks", "block_tokens",
                    "max_context", "vocab", "model_seed"):
            if saved.get(key) is not None and saved[key] != getattr(cfg, key):
                raise ServingError(
                    f"snapshot {key}={saved[key]} != config {getattr(cfg, key)}"
                )
        engine = cls(cfg)
        engine.cache.k[...] = np.asarray(arrays["kv_k"], np.float32)
        engine.cache.v[...] = np.asarray(arrays["kv_v"], np.float32)
        engine.steps = int(extra.get("steps") or 0)
        engine.tokens_generated = int(extra.get("tokens_generated") or 0)
        engine.requests_completed = int(extra.get("requests_completed") or 0)
        engine.requests_rejected = int(extra.get("requests_rejected") or 0)
        engine.requests_cancelled = int(extra.get("requests_cancelled") or 0)
        engine._completions = list(extra.get("completions") or [])
        engine._ttft.extend(extra.get("ttft_samples") or [])
        engine._tpot.extend(extra.get("tpot_samples") or [])
        # reclaim the snapshot's block ownership from the fresh free list
        owned: list[int] = []
        for entry in extra.get("requests") or []:
            req = Request.from_snapshot(entry)
            owned.extend(req.blocks)
            if req.state == QUEUED:
                engine.queued.append(req)
            elif req.state == PREFILL:
                engine.prefilling.append(req)
            elif req.state == RUNNING:
                engine.running.append(req)
        owned_set = set(owned)
        engine.cache._free = [
            b for b in engine.cache._free if b not in owned_set
        ]
        heapq.heapify(engine.cache._free)
        engine.cache._free_set = set(engine.cache._free)
        engine.check_integrity()
        return engine


# ---------------------------------------------------------------------------
# The replica main loop (the serve soak's payload).


def serve(
    cfg: ServeConfig,
    traffic: PoissonTraffic,
    duration_s: float,
    ckpt_dir: str = "",
    sig: Optional[ckpt_api.MigrationSignal] = None,
    progress: Optional[Callable[[dict], None]] = None,
    step_interval_s: float = 0.01,
    clock: Callable[[], float] = time.monotonic,
) -> dict:
    """Real-time serving until ``duration_s`` of service elapse or the
    migration signal lands.  Elapsed service time (not wall time of one
    process) is the clock: a restored replica picks up at the snapshot's
    elapsed point and serves the REMAINDER, with the traffic cursor and
    every in-flight request intact."""
    sig = sig or ckpt_api.MigrationSignal()
    elapsed0 = 0.0
    resumed = False
    engine: Optional[ServingEngine] = None
    if ckpt_dir:
        snap = ckpt_api.load_checkpoint(ckpt_dir)
        if snap is not None:
            engine = ServingEngine.from_snapshot(cfg, snap.arrays, snap.extra)
            serve_state = snap.extra.get("serve") or {}
            elapsed0 = float(serve_state.get("elapsed_s") or 0.0)
            if serve_state.get("traffic"):
                traffic.restore(serve_state["traffic"])
            resumed = True
    if engine is None:
        engine = ServingEngine(cfg)
    if progress is not None:
        progress({
            "event": "restored" if resumed else "started",
            "elapsed_s": round(elapsed0, 3),
            "resumed_requests": engine.active if resumed else 0,
            "tokens_total": engine.tokens_generated,
        })

    t0 = clock()
    last_report = 0.0
    migrated_out = False

    def now_elapsed() -> float:
        return elapsed0 + (clock() - t0)

    while True:
        now = now_elapsed()
        if now >= duration_s and engine.active == 0:
            break
        if sig.requested():
            migrated_out = True
            break
        # step-phase attribution (obs/profile.py): admission from the
        # traffic model is the host-input span, the batched
        # prefill+decode tick is compute
        timer = obs_profile.StepTimer()
        t_step0 = time.perf_counter()
        if now < duration_s:
            with timer.phase(obs_profile.PHASE_HOST_INPUT):
                for req in traffic.due(now):
                    engine.submit(req)
        with timer.phase(obs_profile.PHASE_COMPUTE):
            stats = engine.step(now)
        metrics = engine.telemetry(now)
        flight.record(cfg.name, "step", step=engine.steps, **metrics)
        flight.record_step(
            cfg.name, step_seq=engine.steps,
            wall_s=time.perf_counter() - t_step0, phases=timer.spans(),
        )
        if progress is not None and now - last_report >= 1.0:
            last_report = now
            progress({
                "event": "serving",
                "elapsed_s": round(now, 3),
                "tokens_total": engine.tokens_generated,
                "completed": engine.requests_completed,
                "queue_depth": stats["queue_depth"],
                "batch": stats["batch"],
                # optional: the throughput gauge goes dark while idle
                "tokens_per_sec": metrics.get("serve_tokens_per_sec", 0.0),
            })
        # pace the loop: decode-bound, not spin-bound
        spent = now_elapsed() - now
        if step_interval_s > spent:
            time.sleep(step_interval_s - spent)

    final_elapsed = now_elapsed()
    checkpointed = False
    if migrated_out and ckpt_dir:
        arrays, extra = engine.snapshot()
        extra["serve"] = {
            "elapsed_s": final_elapsed,
            "traffic": traffic.state(),
        }
        writer = ckpt_api.Checkpointer(ckpt_dir)
        writer.save(engine.steps, arrays, extra=extra, final=True)
        checkpointed = True
        if progress is not None:
            progress({
                "event": "checkpointed",
                "trigger": "migrate-signal",
                "step": engine.steps,
                "tokens_total": engine.tokens_generated,
                "in_flight": engine.active,
            })

    completions = engine.completions()
    tpots = sorted(c["tpot_mean_s"] for c in completions if c["tpot_mean_s"])
    ttfts = sorted(
        c["ttft_s"] for c in completions if c.get("ttft_s") is not None
    )
    return {
        # a drained replica that could not honor the migration contract
        # (signal received, no snapshot published — in-flight requests
        # silently dropped) must NOT exit 0: the coordinator reads exit 0
        # as checkpoint-complete
        "ok": checkpointed or not migrated_out,
        "resumed": resumed,
        "migrated_out": migrated_out,
        "checkpointed": checkpointed,
        "elapsed_s": round(final_elapsed, 3),
        "steps": engine.steps,
        "tokens_total": engine.tokens_generated,
        "requests_completed": engine.requests_completed,
        "requests_rejected": engine.requests_rejected,
        "in_flight_at_exit": engine.active,
        # tokens_total spans the whole serving lifetime (snapshots carry
        # the counter), so the rate denominator is total elapsed service
        "tokens_per_sec": round(
            engine.tokens_generated / max(1e-6, final_elapsed), 3
        ),
        "ttft_p50_s": round(_percentile(ttfts, 0.5), 6),
        "ttft_p99_s": round(_percentile(ttfts, 0.99), 6),
        "tpot_p50_s": round(_percentile(tpots, 0.5), 6),
        "tpot_p99_s": round(_percentile(tpots, 0.99), 6),
    }


# ---------------------------------------------------------------------------
# The acceptance A/B: continuous batching vs sequential scheduling.


def batching_ab(
    n_requests: int = 24,
    prompt_tokens: int = 48,
    new_tokens: int = 32,
    max_batch: int = 8,
    seed: int = 7,
    cfg: Optional[ServeConfig] = None,
) -> dict:
    """The same seeded closed-loop request set (all arrive at t=0) through
    (a) sequential one-request-at-a-time scheduling and (b) continuous
    batching — IDENTICAL compiled shapes (both pad to ``max_batch``), so
    the only variable is the scheduler.  Returns both runs' aggregate
    tokens/sec and per-request mean-TPOT percentiles, plus the
    batch-invariance verdict (every request's token stream must be
    identical across the two runs — throughput must not buy different
    results)."""
    base = cfg or ServeConfig(max_batch=max_batch)

    def _requests() -> list[Request]:
        rng = np.random.default_rng(seed)
        return [
            Request(
                rid=f"ab-{i}",
                prompt=[int(t) for t in rng.integers(0, base.vocab, prompt_tokens)],
                max_new_tokens=new_tokens,
                arrival=0.0,
            )
            for i in range(n_requests)
        ]

    def _run_streams(admit_limit: int) -> tuple[dict, dict[str, list[int]]]:
        cfg_run = ServeConfig(
            vocab=base.vocab, heads=base.heads, head_dim=base.head_dim,
            num_blocks=base.num_blocks, block_tokens=base.block_tokens,
            max_batch=base.max_batch, max_context=base.max_context,
            prefill_budget=base.prefill_budget, admit_limit=admit_limit,
            attend=base.attend, model_seed=base.model_seed,
        )
        engine = ServingEngine(cfg_run)
        reqs = _requests()
        for req in reqs:
            assert engine.submit(req)
        t0 = time.perf_counter()
        guard = 0
        while engine.active and guard < 1_000_000:
            engine.step(time.perf_counter() - t0)
            guard += 1
        wall = max(1e-9, time.perf_counter() - t0)
        comps = engine.completions()
        tpots = sorted(c["tpot_mean_s"] for c in comps if c["tpot_mean_s"])
        streams = {
            req.rid: req.tokens[len(req.prompt):] for req in reqs
        }
        return {
            "tokens": engine.tokens_generated,
            "wall_s": round(wall, 4),
            "tokens_per_sec": round(engine.tokens_generated / wall, 2),
            "completed": engine.requests_completed,
            "tpot_p50_s": _percentile(tpots, 0.5),
            "tpot_p99_s": _percentile(tpots, 0.99),
            "steps": engine.steps,
        }, streams

    # warm the attention path BEFORE timing either run: a one-time compile
    # landing inside the first (sequential) timed run would deflate its
    # rate and flatter the A/B — the comparison is scheduling, nothing
    # else.  Dense warms its single jitted shape directly; flash (many
    # per-length shapes) warms via one untimed throwaway run.
    if base.attend == "dense":
        warm = _dense_attend(
            base.max_batch, base.max_context, base.heads, base.head_dim
        )
        np.asarray(warm(
            np.zeros((base.max_batch, base.heads, base.head_dim), np.float32),
            np.zeros((base.max_batch, base.max_context, base.heads,
                      base.head_dim), np.float32),
            np.zeros((base.max_batch, base.max_context, base.heads,
                      base.head_dim), np.float32),
            np.ones((base.max_batch,), np.int32),
        ))
    else:
        _run_streams(admit_limit=0)

    sequential, seq_streams = _run_streams(admit_limit=1)
    batched, batch_streams = _run_streams(admit_limit=0)
    identical = seq_streams == batch_streams
    speedup = (
        batched["tokens_per_sec"] / sequential["tokens_per_sec"]
        if sequential["tokens_per_sec"] else 0.0
    )
    return {
        "ok": bool(
            identical
            and sequential["completed"] == n_requests
            and batched["completed"] == n_requests
        ),
        "n_requests": n_requests,
        "prompt_tokens": prompt_tokens,
        "new_tokens": new_tokens,
        "max_batch": max_batch,
        "sequential": sequential,
        "batched": batched,
        "speedup": round(speedup, 3),
        "identical_outputs": identical,
    }


def quick_check() -> dict:
    """The validator's opt-in serving probe: a small closed-loop A/B —
    continuous batching must beat sequential scheduling on this node with
    identical per-request outputs (``ok`` covers both)."""
    result = batching_ab(n_requests=8, prompt_tokens=24, new_tokens=12)
    result["check"] = "serving"
    # report-only speedup plus the hard correctness half: a node where
    # batching CHANGES results is broken in a way throughput cannot excuse
    result["ok"] = bool(result["identical_outputs"]) and result["ok"]
    return result


# ---------------------------------------------------------------------------
# Module main: the serve-soak replica payload.


def _int_range(env: str, default: tuple[int, int]) -> tuple[int, int]:
    raw = os.environ.get(env, "")
    if not raw:
        return default
    try:
        lo, _, hi = raw.partition(",")
        lo_i, hi_i = int(lo), int(hi or lo)
        return (lo_i, max(lo_i, hi_i))
    except ValueError:
        return default


def main() -> int:
    from tpu_operator import workloads
    from tpu_operator.validator import status as vstatus

    workloads.honor_cpu_platform_request()
    name = os.environ.get(NAME_ENV, "serving")
    cfg = ServeConfig(
        num_blocks=int(os.environ.get(BLOCKS_ENV, "96") or 96),
        block_tokens=int(os.environ.get(BLOCK_TOKENS_ENV, "16") or 16),
        max_batch=int(os.environ.get(MAX_BATCH_ENV, "8") or 8),
        prefill_budget=int(os.environ.get(PREFILL_BUDGET_ENV, "64") or 64),
        name=name,
    )
    traffic = PoissonTraffic(
        rate=float(os.environ.get(RATE_ENV, "3") or 3),
        prompt_tokens=_int_range(PROMPT_TOKENS_ENV, (24, 64)),
        new_tokens=_int_range(NEW_TOKENS_ENV, (12, 32)),
        vocab=cfg.vocab,
        seed=int(os.environ.get(SEED_ENV, "0") or 0),
        prefix=name,
    )
    duration = float(os.environ.get(SECONDS_ENV, "30") or 30)
    step_interval = float(os.environ.get(STEP_INTERVAL_ENV, "0.01") or 0.01)
    ckpt_dir = os.environ.get(consts.CKPT_DIR_ENV, "")
    if ckpt_dir:
        os.makedirs(ckpt_dir, exist_ok=True)
    result_file = os.environ.get("TPU_JOB_RESULT_FILE", "")

    def progress(event: dict) -> None:
        line = json.dumps({"ts": round(time.time(), 3), **event})
        print(line, flush=True)
        if result_file:
            try:
                with open(result_file, "a") as f:
                    f.write(line + "\n")
            except OSError:
                pass

    recorder = flight.recorder_for(vstatus.flight_record_path(name))
    with flight.activate(recorder):
        result = serve(
            cfg,
            traffic,
            duration_s=duration,
            ckpt_dir=ckpt_dir,
            progress=progress,
            step_interval_s=step_interval,
        )
        flight.record_result(name, result)
    progress({"event": "result", **result})
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
