"""Shared chained-dispatch timing methodology for the perf benchmarks.

All three benchmarks (collectives allreduce, matmul MFU, HBM streaming) use
the same r03 recipe: run the op chain inside ONE compiled program with a
scalar readback (per-dispatch timing is untrustworthy on tunneled PJRT
backends), measure the dispatch+readback floor with a null program of the
same shape, subtract it, best-of-N.  This module is the single home of the
two pieces they must keep identical: the wall-clock probe and the
floor-subtraction / overhead-domination rule.
"""

from __future__ import annotations

import os
import time
from typing import Optional


def gate_backends(env_var: str, default: str = "tpu") -> list[str]:
    """Backends a gate is enforced on (one parsing rule for every gate):
    CPU/gloo numbers say nothing about chip health, so gates default to the
    tpu backend only; tests widen via the env var."""
    return [b.strip() for b in os.environ.get(env_var, default).split(",")]


def timed(fn) -> float:
    """Wall-clock one call; ``fn`` must synchronize internally (e.g. a
    float() readback)."""
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def subtract_floor(
    raw: list[float], floor: float, per: int = 1
) -> tuple[list[float], bool]:
    """(sorted per-unit times with the floor subtracted, overhead_dominated).

    One rule everywhere: when the floor rivals the raw measurement
    (floor > raw/2, or subtraction goes non-positive) the measurement is
    flagged overhead-dominated — the per-unit times then fall back to the
    raw amortized values, and callers must never gate on a flagged number
    in either direction."""
    times = sorted((t - floor) / per for t in raw)
    dominated = times[0] <= 0 or floor > 0.5 * min(raw)
    if dominated:
        times = sorted(t / per for t in raw)
    return times, dominated


def regression_verdict(
    current,
    prior,
    threshold: float = 0.07,
    higher_is_better: bool = True,
) -> Optional[dict]:
    """The ONE round-over-round comparison rule (bench.py verdicts and the
    validator's regression Events must agree on what "regressed" means):
    relative delta against the prior value, verdict ``improved`` / ``flat``
    / ``regressed`` outside/inside the ``threshold`` band.

    The default band (7%) sits just above the measured run-to-run envelope
    on the tunneled runner (±3-6%, within-run samples correlated — see
    bench.py _best_of_runs): a single-run wobble must not page anyone, a
    real drop (the r01→r02 19% allreduce loss) must.  Returns None when
    either side is unusable (missing, zero prior, non-numeric) — absence
    of a verdict is itself evidence the metric wasn't comparable."""
    if (
        not isinstance(current, (int, float))
        or not isinstance(prior, (int, float))
        or isinstance(current, bool)
        or isinstance(prior, bool)
        or prior == 0
    ):
        return None
    delta = (current - prior) / abs(prior)
    signed = delta if higher_is_better else -delta
    if signed >= threshold:
        verdict = "improved"
    elif signed <= -threshold:
        verdict = "regressed"
    else:
        verdict = "flat"
    return {
        "verdict": verdict,
        "current": current,
        "prior": prior,
        "delta_pct": round(delta * 100, 2),
    }


def apply_min_gate(
    result: dict,
    *,
    metric: str,
    minimum: float,
    backends_env: str,
    label: str,
    min_key: str = "min_gbps",
    require_ici: bool = False,
) -> dict:
    """The bandwidth-gate enforcement rule, in ONE place (allreduce, ring
    and HBM gates must stay identical):

    - enforce only when a positive minimum is set
    - only on backends named in the ``backends_env`` env var (default tpu —
      CPU/gloo rates say nothing about chip health; tests widen it)
    - with ``require_ici``, only over real inter-chip transport (single-chip
      HBM copy rates are never gated as ICI)
    - never when the measurement was overhead-dominated (can't be trusted
      in either direction)

    Mutates ``result``: records the minimum under ``min_key`` and whether
    the gate was actually ``gated`` (enforced), and flips ``ok`` on a miss."""
    backends = gate_backends(backends_env)
    enforced = (
        minimum > 0
        and (not require_ici or result.get("transport") == "ici")
        and result.get("backend") in backends
        and not result.get("overhead_dominated")
    )
    result[min_key] = minimum
    result["gated"] = enforced
    if enforced and result[metric] < minimum:
        result["ok"] = False
        result["error"] = (
            f"{label} {result[metric]:.1f} GB/s below required {minimum:g}"
        )
    return result
