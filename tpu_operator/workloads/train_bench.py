"""Training-throughput benchmark: the flagship step's tokens/sec and MFU.

The operator's perf story so far measures primitives (matmul MFU, HBM
streaming, collective bandwidths); this measures what a USER of the node
gets — full train steps of the flagship transformer layer (dp + ring-
attention SP + Megatron-SP TP, `collectives.transformer_step`) including
forward, backward through the remat ring attention, and the SGD update
with its gradient collectives.

Methodology follows the repo timing rule (workloads/timing.py): ``steps``
SGD iterations run inside ONE compiled ``lax.scan`` with a single scalar
readback — per-dispatch timing is untrustworthy on tunneled PJRT
backends — and the dispatch+readback floor (a null program) is
subtracted, with the overhead-dominated flag set when the floor rivals
the measurement (callers must never gate on a flagged number).

MFU accounting: analytic model FLOPs per step = 3 x forward (the
backward's ~2x, the remat recompute counted as overhead, not useful
work), forward = 24·b·s·d² (QKVO + the 4d MLP) + 4·b·s²·d (scores + PV,
causal masking NOT discounted — the PaLM convention, so figures compare
with published MFU numbers).
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_operator.obs import flight
from tpu_operator.obs import profile as obs_profile
from tpu_operator.workloads import timing


def step_model_flops(batch: int, seq: int, d_model: int, d_hidden: int) -> float:
    """Analytic model FLOPs for one train step of the flagship layer."""
    fwd_proj = 8.0 * batch * seq * d_model * d_model          # Q,K,V,O
    fwd_mlp = 4.0 * batch * seq * d_model * d_hidden          # two halves
    fwd_attn = 4.0 * batch * seq * seq * d_model              # scores + PV
    return 3.0 * (fwd_proj + fwd_mlp + fwd_attn)


def train_benchmark(
    batch_per_dp: int = 4,
    seq_per_mp: int = 2048,
    d_model: int = 4096,
    d_hidden: int = 16384,
    heads: int = 32,
    steps: int = 4,
    best_of: int = 3,
    devices: Optional[list] = None,
    use_pallas: Optional[bool] = None,
) -> dict:
    """Measure sustained train-step throughput on all local chips.

    Returns tokens/sec, step time, model TFLOPs/s and (when the chip
    generation's peak is known) training MFU."""
    from tpu_operator.k8s.nodeinfo import generation_info
    from tpu_operator.workloads import collectives, matmul_bench

    devices = devices if devices is not None else jax.devices()
    if use_pallas is None:
        # the fused fwd + FA2-backward kernels measured 0.69-0.79 training
        # MFU vs the jnp path's 0.58-0.65 on v5e (the backward kernel is
        # the difference: jnp materializes four score-sized HBM tensors
        # per hop); CPU stays jnp — interpret-mode kernels crawl
        use_pallas = jax.default_backend() == "tpu"
    n = len(devices)
    mesh = collectives.make_mesh(devices=devices)
    dp, mp = mesh.shape["dp"], mesh.shape["mp"]
    b, s = batch_per_dp * dp, seq_per_mp * mp

    sharding = NamedSharding(mesh, P("dp", "mp", None))
    params = collectives.transformer_params(mesh, d_model=d_model, d_hidden=d_hidden)

    def init(key):
        return jax.random.normal(key, (b, s, d_model), jnp.bfloat16)

    x = jax.jit(init, out_shardings=sharding)(jax.random.PRNGKey(2))

    @jax.jit
    def run(params, x):
        def body(params, _):
            loss, params = collectives.transformer_step(
                mesh, heads, params, x, use_pallas=use_pallas
            )
            return params, loss
        params, losses = jax.lax.scan(body, params, None, length=steps)
        return losses[-1], params

    @jax.jit
    def null(x):
        return jnp.sum(x[0, 0].astype(jnp.float32))

    float(null(x))  # compile
    overhead = min(timing.timed(lambda: float(null(x))) for _ in range(3))

    t_compile = time.perf_counter()
    loss, warm_params = run(params, x)  # compile + settle
    loss0 = float(loss)
    flight.record(
        "train", "compile", compile_s=time.perf_counter() - t_compile
    )

    raw = []
    for rep in range(best_of):
        t0 = time.perf_counter()
        loss, warm_params = run(warm_params, x)
        float(loss)
        raw.append(time.perf_counter() - t0)
        flight.record(
            "train", "step", step=rep,
            step_s=raw[-1] / steps,
            tokens_per_sec=b * s * steps / raw[-1],
        )
        flight.record_step(
            "train", step_seq=rep, wall_s=raw[-1],
            phases={obs_profile.PHASE_COMPUTE: raw[-1]},
        )
    times, overhead_dominated = timing.subtract_floor(raw, overhead, per=steps)
    step_s = times[0]
    step_s_median = times[len(times) // 2]

    flops = step_model_flops(b, s, d_model, d_hidden)
    tflops = flops / step_s / 1e12
    generation = matmul_bench.detect_generation()
    peak = generation_info(generation).peak_bf16_tflops * n
    result = {
        "ok": bool(np.isfinite(loss0)),
        "devices": n,
        "mesh": {"dp": dp, "mp": mp},
        "batch": b,
        "seq": s,
        "d_model": d_model,
        "d_hidden": d_hidden,
        "steps_per_run": steps,
        "overhead_ms": overhead * 1e3,
        "overhead_dominated": overhead_dominated,
        "step_time_ms": step_s * 1e3,
        "step_time_ms_median": step_s_median * 1e3,
        "step_time_ms_max": times[-1] * 1e3,
        "tokens_per_sec": b * s / step_s,
        "tokens_per_sec_spread": {
            "min": b * s / times[-1],
            "median": b * s / step_s_median,
            "max": b * s / step_s,
        },
        "model_tflops": tflops,
        "backend": jax.default_backend(),
        "generation": generation,
        # names BOTH kernels: use_pallas selects the fused forward AND
        # the FA2 block backward (the backward is the MFU difference)
        "attention_kernel": "pallas-flash-fwd-bwd" if use_pallas else "jnp",
    }
    if peak > 0:
        result["train_mfu"] = round(tflops / peak, 4)
        result["train_mfu_median"] = round(flops / step_s_median / 1e12 / peak, 4)
        result["train_mfu_min"] = round(flops / times[-1] / 1e12 / peak, 4)
    return result


def quick_check() -> dict:
    """The validator's probe: real shapes on TPU; tiny shapes elsewhere
    (the scan over full train steps would crawl on CPU)."""
    if jax.default_backend() == "tpu":
        return train_benchmark()
    return train_benchmark(
        batch_per_dp=2, seq_per_mp=32, d_model=64, d_hidden=128, heads=4,
        steps=2, best_of=2,
    )


def main() -> int:
    import json

    from tpu_operator import workloads
    from tpu_operator.workloads import compile_cache

    workloads.honor_cpu_platform_request()
    compile_cache.enable()
    result = quick_check()
    flight.record_result("train", result)
    flight.close_active()
    print(json.dumps(result), flush=True)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
