"""Ulysses sequence parallelism: all-to-all attention over the head axis.

The second long-context strategy next to ring attention (SURVEY §2.6: the
reference has no sequence-parallel concept; the TPU build treats
long-context as first-class, with selectable strategies).  Where the ring
keeps the sequence sharded and rotates K/V blocks p times over ICI
neighbours, Ulysses (DeepSpeed-Ulysses, Jacobs et al.) pays exactly TWO
all-to-alls: the first re-shards [B, T/p, H, D] → [B, T, H/p, D] (every
chip trades sequence blocks for whole heads), each chip then runs plain
full-sequence attention over its H/p heads, and the second all-to-all
re-shards the output back to [B, T/p, H, D].

Trade-off vs the ring (why both exist): Ulysses moves 2·T·H·D elements
per chip in two dense all-to-alls (latency-bound at small shapes,
bandwidth-optimal on a full-mesh ICI), needs H divisible by p, and peaks
memory at T×(H/p) — the full sequence per chip.  The ring never
materialises the full sequence (block memory O(T/p)), works for any head
count, and overlaps its p−1 ppermute hops with compute, but serialises
those hops around the ring.  Short-ish sequences with many heads →
Ulysses; extreme sequence lengths or few heads → ring.

Exactness: attention per head is untouched — no online-softmax machinery
is even needed; the acceptance check pins the result against the same
single-device reference the ring uses.
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_operator.workloads.ring_attention import reference_attention


def ulysses_attention_sharded(q, k, v, axis_name: str, causal: bool) -> jax.Array:
    """The per-shard program (call under shard_map with the sequence axis
    sharded over ``axis_name``).  Shapes [B, T/p, H, D]; requires
    H % p == 0 (heads must split evenly across the axis)."""
    p = jax.lax.psum(1, axis_name)
    h = q.shape[2]
    # static head count vs dynamic axis size: the check must live in the
    # trace, where p is an abstract value — guard with a where-poison-free
    # host assert only when p is concrete (single-trace shard_map gives a
    # concrete int via mesh shape at bind time)
    if isinstance(p, int) and h % p != 0:
        raise ValueError(f"ulysses needs heads ({h}) divisible by axis size ({p})")

    def seq_to_heads(x):
        # [B, T/p, H, D] → [B, T, H/p, D]: split the head axis p ways,
        # concatenate the sequence axis — one XLA AllToAll on the MXU-free
        # ICI path, no host round trip
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = reference_attention(qh, kh, vh, causal)  # full-seq, H/p heads
    return heads_to_seq(out.astype(q.dtype))


def ulysses_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh, causal: bool = True
) -> jax.Array:
    """Sequence-parallel attention over a 1-D mesh axis "x"; inputs/outputs
    sequence-sharded [B, T, H, D] — drop-in for ring_attention()."""
    fn = functools.partial(ulysses_attention_sharded, axis_name="x", causal=causal)
    shard = jax.shard_map(
        fn, mesh=mesh,
        in_specs=(P(None, "x"), P(None, "x"), P(None, "x")),
        out_specs=P(None, "x"),
    )
    return shard(q, k, v)


def acceptance(
    batch: int = 1,
    seq_per_chip: int = 128,
    heads: int = 8,
    head_dim: int = 64,
    causal: bool = True,
    devices: Optional[list] = None,
    tol: float = 2e-2,
) -> dict:
    """Run Ulysses attention over every local chip and verify it matches
    the single-device reference (bf16 tolerance).  Returns the
    check-result dict (run_validation shape)."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    mesh = Mesh(np.array(devices), ("x",))
    t = seq_per_chip * n
    if heads % n != 0:
        # keep the acceptance runnable on any chip count: round heads up
        # to a multiple of the axis size rather than skip (the result
        # dict reports the adjusted count)
        heads = ((heads + n - 1) // n) * n
    sharding = NamedSharding(mesh, P(None, "x"))

    def init(key):
        kq, kk, kv = jax.random.split(key, 3)
        shape = (batch, t, heads, head_dim)
        return tuple(
            jax.random.normal(kk_, shape, jnp.bfloat16) for kk_ in (kq, kk, kv)
        )

    qs, ks, vs = jax.jit(init, out_shardings=(sharding,) * 3)(jax.random.PRNGKey(0))

    @jax.jit
    def program(qs, ks, vs):
        out = ulysses_attention(qs, ks, vs, mesh, causal=causal)
        ref = reference_attention(qs, ks, vs, causal)
        return jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32)))

    t0 = time.perf_counter()
    err = float(program(qs, ks, vs))
    dt = time.perf_counter() - t0
    from tpu_operator.obs import flight

    flight.record("ulysses", "run", step_s=dt, seq=t, max_error=err)
    return {
        "ok": bool(np.isfinite(err) and err < tol),
        "devices": n,
        "seq": t,
        "seq_per_chip": seq_per_chip,
        "heads": heads,
        "head_dim": head_dim,
        "causal": causal,
        "strategy": "ulysses-all-to-all",
        "max_error": err,
        "time_s": dt,
        "backend": jax.default_backend(),
    }


def quick_check() -> dict:
    """The validator's probe: real shapes on TPU; tiny shapes elsewhere."""
    if jax.default_backend() == "tpu":
        return acceptance(seq_per_chip=512, head_dim=128)
    return acceptance(seq_per_chip=16, heads=8, head_dim=8)


def main() -> int:
    import json
    import sys

    from tpu_operator import workloads
    from tpu_operator.workloads import compile_cache

    workloads.honor_cpu_platform_request()
    compile_cache.enable()
    result = quick_check()
    from tpu_operator.obs import flight

    flight.record_result("ulysses", result)
    flight.close_active()
    print(json.dumps(result), flush=True)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
