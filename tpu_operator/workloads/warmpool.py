"""Warm-pool validation: the validator's XLA programs through the fleet
compile-artifact cache.

The join→validated phase breakdown (PR 7) proved XLA compilation dominates
the validator's critical path.  This module is the cached replacement for
paying that compile on every node: the *canonical program set* — the same
shapes every validator of a (generation, topology, versions) kind proves —
is compiled through :mod:`tpu_operator.workloads.compile_cache`'s AOT path:

1. trace+lower each program (milliseconds) and fingerprint the lowered
   StableHLO — the program half of the :class:`~.compile_cache.CacheKey`;
2. hit the node-local artifact store, else the prewarmed fleet artifacts,
   else compile (the one cold path) and publish;
3. EXECUTE the loaded executable and verify its output is finite — a cache
   hit still proves the chip runs the program, it only skips the compiler.

Runs as the ``warm-pool`` check inside ``run_validation`` (opt-in via
``WORKLOAD_CHECKS``) and as the per-node validation body of
``bench.py --join``.  Every figure lands in the flight record (compile_s,
cache hits/misses/bytes) so the agent push → fleet aggregator chain sees
per-node warm/cold evidence.

Env contract (injected by the validator's workload-pod spec):
- ``TPU_COMPILE_CACHE_ARTIFACTS`` — node-local artifact dir (under the
  compile-cache hostPath); unset ⇒ no artifact cache, every program
  compiles (tests and dryruns never write persistent state implicitly).
- ``TPU_FLEET_CACHE_URL`` — the fleet cache (agent relay or operator
  surface); unset ⇒ node-local only.
- ``TPU_CACHE_GENERATION`` / ``TPU_CACHE_TOPOLOGY`` /
  ``TPU_LIBTPU_VERSION`` — the hardware/software half of the cache key.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional

from tpu_operator.workloads import compile_cache as cc

GENERATION_ENV = "TPU_CACHE_GENERATION"
TOPOLOGY_ENV = "TPU_CACHE_TOPOLOGY"


def key_fields() -> dict:
    """The non-program :class:`~.compile_cache.CacheKey` fields for this
    process, from the env contract plus the live jax version."""
    jax_version, libtpu_version = cc.current_versions()
    return {
        "generation": os.environ.get(GENERATION_ENV, ""),
        "topology": os.environ.get(TOPOLOGY_ENV, ""),
        "jax_version": jax_version,
        "libtpu_version": libtpu_version,
    }


def kind_from_env() -> str:
    fields = key_fields()
    if not fields["generation"] and not fields["topology"]:
        return ""
    return cc.kind_fingerprint(**fields)


def validation_programs() -> dict[str, Callable[[], tuple]]:
    """name → builder returning ``(fn, args)``.  Builders return FRESH
    function objects so jax's in-memory jit cache never masks a compile
    that a separate validator process would pay — per-program cost is
    honest even when several simulated nodes share one process (the
    ``bench.py --join`` tier).  The set mirrors the validation gate:
    element-wise (vector-add), a reduction chain (the allreduce shape),
    and the layered matmul step whose compile dominates real joins."""
    import jax.numpy as jnp
    import numpy as np

    def vector_add():
        x = jnp.asarray(np.arange(1 << 12, dtype=np.float32))

        def fn(a):
            return (a + a).sum()

        return fn, (x,)

    def reduce_chain():
        x = jnp.ones((64, 256), dtype=jnp.float32)

        def fn(a):
            for _ in range(4):
                a = a - a.mean(axis=0, keepdims=True)
                a = a / (1.0 + jnp.abs(a).max())
            return a.sum()

        return fn, (x,)

    def train_step():
        x = jnp.ones((256, 256), dtype=jnp.float32)

        def fn(a):
            for _ in range(6):
                a = jnp.tanh(a @ a.T) @ a
            return a.sum()

        return fn, (x,)

    return {
        "vector-add": vector_add,
        "reduce-chain": reduce_chain,
        "train-step": train_step,
    }


def run(
    store: Optional[cc.ArtifactStore] = None,
    client: Optional[cc.FleetCacheClient] = None,
    fields: Optional[dict] = None,
    programs: Optional[dict] = None,
) -> dict:
    """Compile-or-fetch and execute every canonical program.  Returns the
    check result: per-program hit/compile seconds, the store counters, and
    ``ok`` false only on a genuinely wrong execution (non-finite output) —
    cache trouble is never a failure, it just costs compiles."""
    import math

    from tpu_operator.obs import flight

    store = store if store is not None else cc.default_store()
    client = client or cc.FleetCacheClient()
    fields = fields or key_fields()
    programs = programs or validation_programs()
    kind = cc.kind_fingerprint(**fields)

    prewarmed = 0
    if store is not None and client.enabled():
        prewarmed = cc.prewarm(store, kind, client)

    ok = True
    results: dict[str, dict] = {}
    compile_s = 0.0
    fetch_s = 0.0
    t0 = time.perf_counter()
    for name, build in programs.items():
        fn, args = build()
        lowered, program_fp = cc.aot_fingerprint(fn, *args, name=name)
        key = cc.CacheKey(program=program_fp, **fields)
        executable, hit, seconds = cc.compile_or_fetch(store, key, lowered)
        if hit:
            fetch_s += seconds
        else:
            compile_s += seconds
        value = float(executable(*args))
        finite = math.isfinite(value)
        ok = ok and finite
        results[name] = {
            "hit": hit,
            "seconds": round(seconds, 6),
            "finite": finite,
        }
        flight.record(
            "warm-pool",
            phase="compile",
            compile_s=seconds if not hit else 0.0,
            cache_hit=float(hit),
        )

    published = 0
    if store is not None and client.enabled() and store.stats.misses > 0:
        # only a validator that actually COMPILED something new publishes:
        # warm-pool nodes must not re-upload the seeder's artifacts from
        # 10k nodes at once (the fleet side is idempotent regardless)
        published = cc.publish_kind(store, kind, client)
    if store is not None:
        store.record_flight_sample()

    stats = store.stats if store is not None else cc.CacheStats()
    result = {
        "ok": ok,
        "programs": len(results),
        "hits": stats.hits,
        "misses": stats.misses,
        "corrupt": stats.corrupt,
        "prewarmed": prewarmed,
        "published": published,
        "compile_s": round(compile_s, 6),
        "fetch_s": round(fetch_s, 6),
        "duration_s": round(time.perf_counter() - t0, 6),
        "results": results,
    }
    return result


def quick_check() -> dict:
    return run()
