"""Bounded-time failure detection for the multi-host rendezvous.

The distributed runtime's own failure handling is either too slow or too
blunt for slice validation (SURVEY §5.3 — the reference's recovery story
is node-local cordon/drain, upgrade_controller.go:146-196; a coordinated
SET of workers that must fail together is the TPU-specific problem):

- a NON-coordinator worker dying is only noticed after the coordination
  service's heartbeat timeout (100 s by default), and the notification is
  a C++ LOG(FATAL) that kills the survivors with no structured evidence;
- survivors wedged inside a collective whose peer died block at the XLA
  level — the collective itself has no timeout.

This watchdog bounds both from Python.  Every worker publishes a
monotonically increasing heartbeat into the coordination service's
key-value store (KV ops only need the COORDINATOR alive, not the peer)
and a daemon thread checks the peers' beats.  A peer whose beat stalls
past ``timeout`` is declared dead: the watchdog writes structured
evidence — which member died, which phase it and we were in, detection
latency — to the node-local drop-box, prints it as the final stdout line,
and hard-exits (``os._exit`` fires even while the main thread is wedged
inside a collective).  Detection latency is bounded by
``timeout + interval``, independent of the validator's 300 s pod budget.

The COORDINATOR dying is detected even faster, but not by us: every
surviving agent's error-polling RPC fails on socket close and the runtime
aborts the process within ~2 s (client.h LOG(FATAL)) — Python never runs
again.  For that case the watchdog maintains an IN-FLIGHT phase record in
the drop-box at every phase transition; the record survives the abort, so
post-mortem evidence of where each worker was exists even when no Python
handler could.  ``rendezvous_post_mortem`` (workloads/distributed.py)
classifies both shapes from the worker outcomes.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from typing import Optional

_KV_PREFIX = "tpuop/watchdog"

# distinct exit code so orchestrators can tell "this worker's watchdog
# detected a dead PEER" from "this worker itself failed its checks" (1)
WATCHDOG_EXIT_CODE = 3

DEFAULT_TIMEOUT_S = 20.0

# the phase a worker publishes after its last check completes
# (workloads/distributed.py run_worker); a peer parked here has exited
# CLEANLY — its heartbeat stopping is success, not death
TERMINAL_PHASE = "done"


class PeerWatchdog:
    """Heartbeat-based peer liveness for one rendezvous.

    ``client`` is the process's coordination-service client
    (``jax._src.distributed.global_state.client``) — created by
    ``jax.distributed.initialize``, so the watchdog can only start
    post-rendezvous (pre-rendezvous hangs are bounded separately by
    ``initialization_timeout``).
    """

    def __init__(
        self,
        client,
        process_id: int,
        num_processes: int,
        *,
        timeout: float = DEFAULT_TIMEOUT_S,
        interval: Optional[float] = None,
        scope: str = "",
        exit_fn=os._exit,
    ):
        self.client = client
        self.process_id = process_id
        self.num_processes = num_processes
        self.timeout = timeout
        self.interval = interval if interval else max(0.25, min(2.0, timeout / 8))
        self.scope = scope
        self.exit_fn = exit_fn
        self.phase = "post-init"
        self._beat = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started = 0.0
        # peer -> (last value, monotonic time the value last advanced)
        self._last_seen: dict[int, tuple[str, float]] = {}
        # monotonic time KV ops started failing (None while healthy) — one
        # transient RPC hiccup must not be declared a dead coordinator
        self._kv_failing_since: Optional[float] = None

    # ------------------------------------------------------------------
    def start(self) -> None:
        self._started = time.monotonic()
        self._publish_beat()
        self._thread = threading.Thread(
            target=self._run, name="peer-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval)

    def set_phase(self, name: str) -> None:
        """Record the phase the main thread is entering.  The KV publish
        lets PEERS name our phase in their evidence; the drop-box write is
        the record that survives our own death (SIGKILL / runtime abort)."""
        self.phase = name
        self._write_inflight()
        try:
            self.client.key_value_set(
                f"{_KV_PREFIX}/phase/{self.process_id}", name, True
            )
        except Exception as e:  # noqa: BLE001 — phase is evidence, not control flow
            logging.getLogger("tpu_operator.watchdog").debug(
                "phase KV publish failed (drop-box record still holds): %s", e
            )

    # ------------------------------------------------------------------
    def _publish_beat(self) -> None:
        self._beat += 1
        self.client.key_value_set(
            f"{_KV_PREFIX}/hb/{self.process_id}", str(self._beat), True
        )

    # sentinel: the phase READ failed (transient KV error) — distinct from
    # "peer never published a phase" (None); a cycle that cannot rule out
    # clean completion must not declare death
    _PHASE_UNKNOWN = object()

    def _peer_phase(self, peer: int):
        try:
            return self.client.key_value_try_get(f"{_KV_PREFIX}/phase/{peer}")
        except Exception as e:  # noqa: BLE001
            return None if "NOT_FOUND" in str(e) else self._PHASE_UNKNOWN

    def _write_inflight(self) -> None:
        from tpu_operator.validator import status as vstatus

        # read-modify-write: the drop-box write is a wholesale file replace
        # (status.py), and the exporter may scrape mid-run — the previous
        # run's 'distributed' figures must survive alongside the in-flight
        # phase record, not vanish at the first phase transition
        existing = vstatus.read_workload_results(scope=self.scope) or {}
        existing.pop("ts", None)
        existing["distributed_inflight"] = {
            "process_id": self.process_id,
            "num_processes": self.num_processes,
            "phase": self.phase,
            "elapsed_s": round(time.monotonic() - self._started, 3)
            if self._started
            else 0.0,
        }
        vstatus.write_workload_results(existing, scope=self.scope)

    # ------------------------------------------------------------------
    def _kv_failed(self, now: float, err: Exception) -> bool:
        """Record a failed KV cycle; True once failures have persisted past
        ``timeout`` (KV ops are served by the coordinator, so persistent
        failure means the coordinator is gone — but ONE transient RPC
        hiccup under load must not fail a healthy worker.  The runtime's
        own error poll usually aborts us first on real coordinator death;
        this path covers the race where our poll loses the socket before
        it does)."""
        if self._kv_failing_since is None:
            self._kv_failing_since = now
        return now - self._kv_failing_since > self.timeout

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            now = time.monotonic()
            try:
                self._publish_beat()
            except Exception as e:  # noqa: BLE001
                if self._kv_failed(now, e):
                    self._fail_coordinator(e)
                    return
                continue
            kv_healthy = True
            dead: list[dict] = []
            for peer in range(self.num_processes):
                if peer == self.process_id:
                    continue
                value = None
                try:
                    value = self.client.key_value_try_get(
                        f"{_KV_PREFIX}/hb/{peer}"
                    )
                except Exception as e:  # noqa: BLE001
                    if "NOT_FOUND" not in str(e):
                        kv_healthy = False
                        if self._kv_failed(now, e):
                            self._fail_coordinator(e)
                            return
                        continue
                    # not published yet: stale-since = watchdog start
                prev = self._last_seen.get(peer)
                if value is not None and (prev is None or prev[0] != value):
                    self._last_seen[peer] = (value, now)
                    continue
                stale_since = prev[1] if prev else self._started
                stale_for = now - stale_since
                if stale_for > self.timeout:
                    phase = self._peer_phase(peer)
                    if phase == TERMINAL_PHASE or phase is self._PHASE_UNKNOWN:
                        # cleanly-exited peer: it published 'done' before its
                        # heartbeat stopped.  A survivor still mid-run (slow
                        # host, longer check list) must not hard-kill its own
                        # healthy validation over a finished sibling — and
                        # when the phase read itself failed transiently, this
                        # cycle cannot rule clean completion out, so the
                        # verdict waits for the next healthy read.
                        continue
                    dead.append(
                        {
                            "process_id": peer,
                            "stale_for_s": round(stale_for, 3),
                            "phase": phase,
                        }
                    )
            if kv_healthy:
                self._kv_failing_since = None
            if dead:
                self._fail_peers(dead)
                return

    # ------------------------------------------------------------------
    def _fail_peers(self, dead: list[dict]) -> None:
        self._die(
            {
                "type": "peer-heartbeat-lost",
                "dead_members": dead,
                "timeout_s": self.timeout,
            }
        )

    def _fail_coordinator(self, err: Exception) -> None:
        self._die(
            {
                "type": "coordinator-unreachable",
                "dead_members": [{"process_id": 0, "phase": None}],
                "error": str(err)[:500],
            }
        )

    def _die(self, fault: dict) -> None:
        # a thread that outlived stop()'s bounded join (wedged in an RPC
        # that later failed) must never fail a worker whose validation
        # already completed — the success result is written by then and
        # os._exit(3) would flip a passed epoch to failed
        if self._stop.is_set():
            return
        evidence = {
            "ok": False,
            "process_id": self.process_id,
            "num_processes": self.num_processes,
            "phase": self.phase,
            "detected_after_s": round(time.monotonic() - self._started, 3),
            "fault": fault,
            "error": (
                f"watchdog: {fault['type']} "
                f"(members {[d['process_id'] for d in fault['dead_members']]}) "
                f"during phase {self.phase!r}"
            ),
        }
        from tpu_operator.validator import status as vstatus

        vstatus.write_workload_results({"distributed": evidence}, scope=self.scope)
        print(json.dumps(evidence), flush=True)
        sys.stdout.flush()
        self.exit_fn(WATCHDOG_EXIT_CODE)
